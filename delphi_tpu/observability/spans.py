"""Hierarchical span tree + run-scoped recorder.

`phase_span` (delphi_tpu/utils) calls :func:`span_enter`/:func:`span_exit`
on every phase. When no recorder is active both are a single ``is None``
check; when one is (``DELPHI_METRICS_PATH`` / ``repair.metrics.path``), each
phase becomes a node in a tree rooted at the run, carrying its start offset
and wall time, and optionally an event line in a JSONL stream.

Span stacks are thread-local: a span opened on a worker thread whose stack
is empty attaches to the run root rather than to whatever span happens to be
open on another thread — per-thread structure stays honest.
"""

import json
import os
import threading
import time
from typing import Any, Dict, IO, List, Optional

from delphi_tpu.observability import trace as _trace
from delphi_tpu.utils import setup_logger

_logger = setup_logger()


class Span:
    __slots__ = ("name", "start_s", "wall_s", "children", "failed",
                 "device_s", "thread", "_t0", "_rec",
                 "span_id", "trace_parent", "trace_t0")

    def __init__(self, name: str, start_s: float) -> None:
        self.name = name
        self.start_s = start_s
        self.wall_s = 0.0
        self.children: List["Span"] = []
        self.failed = False
        self.device_s: Optional[float] = None
        self.thread: Optional[str] = None
        self._t0 = 0.0
        self._rec: Optional["RunRecorder"] = None
        # Trace identity (observability/trace.py): stamped by
        # trace.span_started when this thread is inside a trace scope.
        self.span_id: Optional[str] = None
        self.trace_parent: Optional[str] = None
        self.trace_t0 = 0.0

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "name": self.name,
            "start_s": round(self.start_s, 6),
            "wall_s": round(self.wall_s, 6),
        }
        if self.device_s is not None:
            d["device_s"] = round(self.device_s, 6)
        if self.failed:
            d["failed"] = True
        if self.thread:
            d["thread"] = self.thread
        d["children"] = [c.to_dict() for c in self.children]
        return d

    def walk(self):
        yield self
        for c in self.children:
            yield from c.walk()


class RunRecorder:
    """Collects the span tree, metrics registry, and optional JSONL event
    stream for one ``RepairModel.run()`` invocation."""

    def __init__(self, name: str,
                 events_path: Optional[str] = None) -> None:
        from delphi_tpu.observability.registry import MetricsRegistry

        self.registry = MetricsRegistry()
        self.started_at = time.time()
        self._t0 = time.perf_counter()
        self.root = Span(name, 0.0)
        self.root._t0 = self._t0
        self.root._rec = self
        # Filled in by profile_trace when a trace is captured during the run,
        # so the report builder knows where to find xplane files to join.
        self.trace_dir: Optional[str] = None
        # Live telemetry plane (observability/live.py), attached by
        # start_recording when DELPHI_METRICS_PORT & co. are configured.
        self.live: Optional[Any] = None
        # Gathered per-rank payloads (observability/report.py), filled at
        # stop_recording on multi-host clusters.
        self.per_process: Optional[List[Dict[str, Any]]] = None
        # Provenance ledger (observability/provenance.py), attached by
        # start_recording when DELPHI_PROVENANCE_PATH & co. are configured.
        # `scorecards` freezes the aggregated per-attribute quality cards at
        # provenance.finalize; `drift` holds the drift-gate verdict when
        # main.py ran one against a baseline report.
        self.provenance: Optional[Any] = None
        self.scorecards: Optional[Dict[str, Any]] = None
        self.drift: Optional[Dict[str, Any]] = None
        # Span-transition clock for the stall watchdog: perf_counter of the
        # last enter/exit plus a monotonically increasing transition count.
        self.last_transition = self._t0
        self.transition_count = 0
        self.current_phase = name
        self._lock = threading.Lock()
        self._tls = threading.local()
        # thread-ident -> (thread name, live stack list). The lists are only
        # mutated by their owning threads; the map lets the watchdog and
        # /metrics read every thread's active spans.
        self._thread_stacks: Dict[int, Any] = {}
        self._events_fh: Optional[IO[str]] = None
        if events_path:
            try:
                parent = os.path.dirname(os.path.abspath(events_path))
                os.makedirs(parent, exist_ok=True)
                self._events_fh = open(events_path, "w")
            except OSError as e:
                _logger.warning(f"cannot open event stream {events_path}: {e}")

    def _stack(self) -> List[Span]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
            thread = threading.current_thread()
            with self._lock:
                self._thread_stacks[thread.ident or 0] = (thread.name, stack)
        return stack

    def elapsed_s(self) -> float:
        return time.perf_counter() - self._t0

    def active_spans(self) -> Dict[str, List[str]]:
        """Live snapshot of every thread's open span stack (root to leaf),
        for the watchdog heartbeat and the /metrics span-depth gauges."""
        with self._lock:
            items = list(self._thread_stacks.values())
        return {name: [s.name for s in stack]
                for name, stack in items if stack}

    def span_depth(self) -> int:
        active = self.active_spans()
        return max((len(v) for v in active.values()), default=0)

    def _mark_transition(self) -> None:
        self.last_transition = time.perf_counter()
        self.transition_count += 1

    def span_enter(self, name: str) -> Span:
        now = time.perf_counter()
        span = Span(name, now - self._t0)
        span._t0 = now
        span._rec = self
        thread = threading.current_thread()
        if thread is not threading.main_thread():
            span.thread = thread.name
        self._stack().append(span)
        self.current_phase = name
        self._mark_transition()
        _trace.span_started(span)
        self.emit_event({"event": "span_enter", "name": name,
                         "t_s": round(span.start_s, 6)})
        return span

    def span_exit(self, span: Span, failed: bool = False) -> None:
        span.wall_s = time.perf_counter() - span._t0
        span.failed = failed
        _trace.span_finished(span, failed=failed)
        stack = self._stack()
        if span in stack:
            # Pop through any spans left open by exceptions below this one.
            while stack and stack[-1] is not span:
                stack.pop()
            stack.pop()
        parent = stack[-1] if stack else self.root
        with self._lock:
            parent.children.append(span)
        self.current_phase = parent.name
        self._mark_transition()
        self.emit_event({"event": "span_exit", "name": span.name,
                         "wall_s": round(span.wall_s, 6),
                         "failed": failed})

    def finish(self) -> None:
        self.root.wall_s = time.perf_counter() - self.root._t0

    def emit_event(self, payload: Dict[str, Any]) -> None:
        fh = self._events_fh
        if fh is None:
            return
        try:
            with self._lock:
                fh.write(json.dumps(payload) + "\n")
                fh.flush()
        except Exception:
            pass

    def close(self) -> None:
        if self._events_fh is not None:
            try:
                self._events_fh.close()
            except Exception:
                pass
            self._events_fh = None


# The process-wide active recorder. Written only by start/stop_recording;
# instrumentation reads it with a single attribute load.
_current: Optional[RunRecorder] = None


def current_recorder() -> Optional[RunRecorder]:
    return _current


def start_recording(name: str,
                    events_path: Optional[str] = None) -> Optional[RunRecorder]:
    """Activates a run recorder, unless one is already active (a nested
    ``run()`` then records into the outer run's tree and returns ``None`` so
    only the outer caller writes a report). When ``DELPHI_METRICS_PORT`` /
    ``repair.metrics.port`` (or a stall timeout) is configured, the live
    telemetry plane — HTTP server, watchdog, resource sampler — starts with
    the recorder and stops with it."""
    global _current
    if _current is not None:
        return None
    _current = RunRecorder(name, events_path=events_path)
    try:
        # run-level trace scope (no-op when DELPHI_TRACE_DIR is unset):
        # spans on this thread become trace events under a fresh trace_id
        _current.trace_token = _trace.begin_run_scope()
    except Exception as e:
        _logger.warning(f"trace plane failed to start: {e}")
    try:
        from delphi_tpu.observability import live
        live.maybe_start(_current)
    except Exception as e:
        # Telemetry must never take the run down with it.
        _logger.warning(f"live telemetry plane failed to start: {e}")
    try:
        from delphi_tpu.observability import provenance
        provenance.maybe_start(_current)
    except Exception as e:
        _logger.warning(f"provenance ledger failed to start: {e}")
    try:
        # compile plane: apply cache-dir/threshold overrides and forward
        # jax compilation-cache events into this run's metrics registry
        from delphi_tpu.parallel import compile_plane
        compile_plane.configure_cache()
        compile_plane.install_cache_listeners()
    except Exception as e:
        _logger.warning(f"compile-plane telemetry failed to start: {e}")
    return _current


def stop_recording(recorder: Optional[RunRecorder]) -> None:
    global _current
    if recorder is None:
        return
    try:
        # snapshot compile-cache dir size/entries into the final report
        from delphi_tpu.parallel import compile_plane
        compile_plane.record_cache_dir_stats()
    except Exception as e:
        _logger.warning(f"compile-cache stats unavailable: {e}")
    recorder.finish()
    try:
        # join xplane device time into the launch ledger, stamp the
        # report's trace/launch_costs sections, flush the ledger, then
        # close the run-level trace scope (exports this thread's events)
        _trace.finalize_run(recorder)
        _trace.end_run_scope(getattr(recorder, "trace_token", None))
        recorder.trace_token = None
    except Exception as e:
        _logger.warning(f"trace plane failed to finalize: {e}")
    try:
        # Freeze the per-attribute scorecards and flush the ledger file
        # before the multi-host gather below ships them cross-rank.
        # Idempotent: main.py may already have finalized early so the drift
        # gate could run while the live /metrics plane was still up.
        from delphi_tpu.observability import provenance
        provenance.finalize(recorder)
    except Exception as e:
        _logger.warning(f"provenance ledger failed to finalize: {e}")
    if recorder.live is not None:
        try:
            recorder.live.stop()
        except Exception as e:
            _logger.warning(f"live telemetry plane failed to stop: {e}")
        recorder.live = None
    # Multi-host: every rank reaches this collective at the end of its run;
    # the gathered per-rank payloads land on recorder.per_process for the
    # report builder (single-process runs skip it entirely).
    try:
        from delphi_tpu.observability.report import gather_per_process
        gather_per_process(recorder)
    except Exception as e:
        _logger.warning(f"multi-host report aggregation failed: {e}")
    recorder.close()
    if _current is recorder:
        _current = None


def span_enter(name: str) -> Optional[Span]:
    rec = _current
    return rec.span_enter(name) if rec is not None else None


def span_exit(span: Optional[Span], failed: bool = False) -> None:
    if span is not None and span._rec is not None:
        span._rec.span_exit(span, failed=failed)
