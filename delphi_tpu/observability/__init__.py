"""Unified observability for the repair pipeline.

Four pieces (see docs/source/observability.rst):

* :mod:`~delphi_tpu.observability.registry` — process-wide metrics registry
  (counters / gauges / histograms). Instrumentation calls the module-level
  helpers re-exported here; they no-op when no run recorder is active.
* :mod:`~delphi_tpu.observability.spans` — hierarchical span tree recorded
  by ``phase_span`` plus the run-scoped :class:`RunRecorder`.
* :mod:`~delphi_tpu.observability.report` — the versioned run-report JSON
  written at the end of ``RepairModel.run()`` when ``DELPHI_METRICS_PATH``
  or the ``repair.metrics.path`` session config is set, including per-phase
  device-time attribution when a profiler trace was captured and a
  ``per_process`` section on multi-host clusters.
* :mod:`~delphi_tpu.observability.live` — the live telemetry plane: an HTTP
  server (``/metrics`` Prometheus text, ``/healthz``, ``/report``) enabled
  via ``DELPHI_METRICS_PORT`` / ``repair.metrics.port``, a stall watchdog,
  and a periodic resource sampler.
* :mod:`~delphi_tpu.observability.provenance` — per-cell repair provenance
  ledger (``DELPHI_PROVENANCE_PATH`` / ``repair.provenance.path``) recording
  detector, candidate-domain size, top-k posterior, and final decision for
  every flagged cell, aggregated into per-attribute quality scorecards in
  the run report (schema v3).
* :mod:`~delphi_tpu.observability.drift` — cross-run drift gate comparing
  the current scorecards against a baseline run report (PSI / JS divergence)
  and emitting ``drift.*`` gauges; wired by ``main.py --baseline-report``.
* :mod:`~delphi_tpu.observability.diff` — the ``report-diff`` CLI
  (``python -m delphi_tpu.observability.diff``) printing metric, phase-time,
  and scorecard deltas between two run-report files.
"""

import os
from typing import Optional

from delphi_tpu.observability.live import (  # noqa: F401
    LivePlane, live_configured, metrics_port,
)
from delphi_tpu.observability.provenance import (  # noqa: F401
    ProvenanceLedger, active_ledger, merge_scorecards, provenance_configured,
    provenance_path, scorecard_summary,
)
from delphi_tpu.observability.registry import (  # noqa: F401
    MetricsRegistry, counter_inc, gauge_max, gauge_set, histogram_observe,
)
from delphi_tpu.observability.report import (  # noqa: F401
    REPORT_KIND, REPORT_SCHEMA_VERSION, SUPPORTED_SCHEMA_VERSIONS,
    attribute_device_time, bench_entry, build_run_report, load_run_report,
    upgrade_run_report, write_run_report,
)
from delphi_tpu.observability.spans import (  # noqa: F401
    RunRecorder, Span, current_recorder, start_recording, stop_recording,
)

# Values accepted as "on" by every boolean observability toggle
# (DELPHI_METRICS_EVENTS, repair.metrics.events, DELPHI_PHASE_HEARTBEAT,
# the live-server toggles, ...). One parser so env and session-conf spellings
# can't drift apart again.
_TRUTHY = frozenset({"1", "true", "yes", "on"})


def _flag_enabled(value: Optional[str]) -> bool:
    """True when ``value`` spells an enabled flag: 1/true/yes/on, any case."""
    return value is not None and str(value).strip().lower() in _TRUTHY


def metrics_path() -> Optional[str]:
    """The configured run-report destination, or ``None`` when the run report
    is disabled (`DELPHI_METRICS_PATH` env wins over the
    ``repair.metrics.path`` session config)."""
    path = os.environ.get("DELPHI_METRICS_PATH")
    if path:
        return path
    from delphi_tpu.session import get_session

    return get_session().conf.get("repair.metrics.path") or None


def events_path_for(path: Optional[str]) -> Optional[str]:
    """JSONL event-stream destination next to the report, enabled by
    ``DELPHI_METRICS_EVENTS`` or ``repair.metrics.events`` (1/true/yes)."""
    if not path:
        return None
    if _flag_enabled(os.environ.get("DELPHI_METRICS_EVENTS")):
        return path + ".events.jsonl"
    from delphi_tpu.session import get_session

    if _flag_enabled(get_session().conf.get("repair.metrics.events")):
        return path + ".events.jsonl"
    return None
