"""Unified observability for the repair pipeline.

Three pieces (see docs/source/observability.rst):

* :mod:`~delphi_tpu.observability.registry` — process-wide metrics registry
  (counters / gauges / histograms). Instrumentation calls the module-level
  helpers re-exported here; they no-op when no run recorder is active.
* :mod:`~delphi_tpu.observability.spans` — hierarchical span tree recorded
  by ``phase_span`` plus the run-scoped :class:`RunRecorder`.
* :mod:`~delphi_tpu.observability.report` — the versioned run-report JSON
  written at the end of ``RepairModel.run()`` when ``DELPHI_METRICS_PATH``
  or the ``repair.metrics.path`` session config is set, including per-phase
  device-time attribution when a profiler trace was captured.
"""

import os
from typing import Optional

from delphi_tpu.observability.registry import (  # noqa: F401
    MetricsRegistry, counter_inc, gauge_max, gauge_set, histogram_observe,
)
from delphi_tpu.observability.report import (  # noqa: F401
    REPORT_KIND, REPORT_SCHEMA_VERSION, attribute_device_time, bench_entry,
    build_run_report, load_run_report, write_run_report,
)
from delphi_tpu.observability.spans import (  # noqa: F401
    RunRecorder, Span, current_recorder, start_recording, stop_recording,
)


def metrics_path() -> Optional[str]:
    """The configured run-report destination, or ``None`` when observability
    is disabled (`DELPHI_METRICS_PATH` env wins over the
    ``repair.metrics.path`` session config)."""
    path = os.environ.get("DELPHI_METRICS_PATH")
    if path:
        return path
    from delphi_tpu.session import get_session

    return get_session().conf.get("repair.metrics.path") or None


def events_path_for(path: str) -> Optional[str]:
    """JSONL event-stream destination next to the report, enabled by
    ``DELPHI_METRICS_EVENTS=1`` or ``repair.metrics.events=true``."""
    if os.environ.get("DELPHI_METRICS_EVENTS") == "1":
        return path + ".events.jsonl"
    from delphi_tpu.session import get_session

    if get_session().conf.get("repair.metrics.events", "").lower() == "true":
        return path + ".events.jsonl"
    return None
