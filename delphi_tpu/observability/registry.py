"""Process-wide metrics registry: counters, gauges, histograms.

The registry itself is a plain locked dict-of-numbers container owned by the
active :class:`~delphi_tpu.observability.spans.RunRecorder`. The module-level
helpers (:func:`counter_inc` & co.) are what instrumented pipeline code calls;
they no-op with a single global ``is None`` check when no run recorder is
active (i.e. neither ``DELPHI_METRICS_PATH`` nor ``repair.metrics.path`` is
set), so always-on instrumentation costs nothing on the default path.
"""

import random
import threading
import zlib
from typing import Any, Dict, List, Optional, Union

Number = Union[int, float]

# How many raw observations a histogram keeps for percentile estimation.
# Beyond this the count/sum/min/max stay exact and p50/p95 come from a
# uniform reservoir sample of _HIST_SAMPLE_CAP observations.
_HIST_SAMPLE_CAP = 512


class _Histogram:
    __slots__ = ("count", "total", "min", "max", "samples", "_rng")

    def __init__(self, name: str = "") -> None:
        self.count = 0
        self.total = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self.samples: List[float] = []
        # Deterministic per-name seed: the same run produces the same
        # reservoir, so reports stay reproducible and diffable.
        self._rng = random.Random(zlib.crc32(name.encode("utf-8")))

    def observe(self, value: float) -> None:
        self.count += 1
        self.total += value
        self.min = value if self.min is None else min(self.min, value)
        self.max = value if self.max is None else max(self.max, value)
        if len(self.samples) < _HIST_SAMPLE_CAP:
            self.samples.append(value)
        else:
            # Algorithm R: every observation (not just the first 512) ends up
            # in the reservoir with probability cap/count, so percentiles
            # cover the whole run instead of its start-up.
            j = self._rng.randrange(self.count)
            if j < _HIST_SAMPLE_CAP:
                self.samples[j] = value

    def summary(self) -> Dict[str, Any]:
        return _summarize(self.count, self.total, self.min, self.max,
                          self.samples)

    def state(self) -> Dict[str, Any]:
        """Picklable raw state (samples included) for cross-process merges."""
        return {"count": self.count, "sum": self.total, "min": self.min,
                "max": self.max, "samples": list(self.samples)}


def _summarize(count: int, total: float, mn: Optional[float],
               mx: Optional[float], samples: List[float]) -> Dict[str, Any]:
    s = sorted(samples)

    def pct(q: float) -> Optional[float]:
        if not s:
            return None
        return s[min(len(s) - 1, int(q * len(s)))]

    return {
        "count": count,
        "sum": total,
        "min": mn,
        "max": mx,
        "mean": (total / count) if count else None,
        "p50": pct(0.50),
        "p90": pct(0.90),
        "p95": pct(0.95),
        "p99": pct(0.99),
    }


class MetricsRegistry:
    """Thread-safe named counters/gauges/histograms with a JSON snapshot."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, float] = {}
        self._histograms: Dict[str, _Histogram] = {}

    def inc(self, name: str, value: Number = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + value

    def set_gauge(self, name: str, value: Number) -> None:
        with self._lock:
            self._gauges[name] = value

    def max_gauge(self, name: str, value: Number) -> None:
        """Keeps the maximum value seen — e.g. peak per-chunk row counts."""
        with self._lock:
            prev = self._gauges.get(name)
            self._gauges[name] = value if prev is None else max(prev, value)

    def observe(self, name: str, value: Number) -> None:
        with self._lock:
            hist = self._histograms.get(name)
            if hist is None:
                hist = self._histograms[name] = _Histogram(name)
            hist.observe(float(value))

    def counter_value(self, name: str) -> float:
        with self._lock:
            return float(self._counters.get(name, 0))

    def gauge_value(self, name: str) -> Optional[float]:
        with self._lock:
            value = self._gauges.get(name)
            return None if value is None else float(value)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "counters": dict(sorted(self._counters.items())),
                "gauges": dict(sorted(self._gauges.items())),
                "histograms": {k: v.summary() for k, v
                               in sorted(self._histograms.items())},
            }

    def export_state(self) -> Dict[str, Any]:
        """Raw, picklable registry contents (histogram reservoirs included)
        — what non-zero ranks ship to rank 0 for the multi-host report."""
        with self._lock:
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "histograms": {k: v.state()
                               for k, v in self._histograms.items()},
            }


def state_snapshot(state: Dict[str, Any]) -> Dict[str, Any]:
    """Summary-form snapshot (same shape as :meth:`MetricsRegistry.snapshot`)
    from one exported raw state."""
    return {
        "counters": dict(sorted(state["counters"].items())),
        "gauges": dict(sorted(state["gauges"].items())),
        "histograms": {
            k: _summarize(h["count"], h["sum"], h["min"], h["max"],
                          h["samples"])
            for k, h in sorted(state["histograms"].items())},
    }


def merge_state_snapshots(states: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Cross-process merge of exported registry states: counters sum (the
    cluster-wide total), gauges keep the max across ranks (peaks), and
    histograms combine exactly on count/sum/min/max with percentiles
    estimated from the concatenated reservoirs."""
    counters: Dict[str, float] = {}
    gauges: Dict[str, float] = {}
    hists: Dict[str, Dict[str, Any]] = {}
    for state in states:
        for k, v in state["counters"].items():
            counters[k] = counters.get(k, 0) + v
        for k, v in state["gauges"].items():
            gauges[k] = v if k not in gauges else max(gauges[k], v)
        for k, h in state["histograms"].items():
            agg = hists.setdefault(k, {"count": 0, "sum": 0.0, "min": None,
                                       "max": None, "samples": []})
            agg["count"] += h["count"]
            agg["sum"] += h["sum"]
            for bound, pick in (("min", min), ("max", max)):
                if h[bound] is not None:
                    agg[bound] = h[bound] if agg[bound] is None \
                        else pick(agg[bound], h[bound])
            agg["samples"].extend(h["samples"])
    return {
        "counters": dict(sorted(counters.items())),
        "gauges": dict(sorted(gauges.items())),
        "histograms": {
            k: _summarize(h["count"], h["sum"], h["min"], h["max"],
                          h["samples"])
            for k, h in sorted(hists.items())},
    }


# Cached reference to the spans module, resolved on first use. Importing
# lazily avoids a registry<->spans import cycle; caching keeps the disabled
# fast path to an attribute load + `is None` check.
_spans_mod = None


def _active_registry() -> Optional[MetricsRegistry]:
    global _spans_mod
    if _spans_mod is None:
        from delphi_tpu.observability import spans
        _spans_mod = spans

    rec = _spans_mod._current
    return rec.registry if rec is not None else None


def counter_inc(name: str, value: Number = 1) -> None:
    reg = _active_registry()
    if reg is not None:
        reg.inc(name, value)


def gauge_set(name: str, value: Number) -> None:
    reg = _active_registry()
    if reg is not None:
        reg.set_gauge(name, value)


def gauge_max(name: str, value: Number) -> None:
    reg = _active_registry()
    if reg is not None:
        reg.max_gauge(name, value)


def histogram_observe(name: str, value: Number) -> None:
    reg = _active_registry()
    if reg is not None:
        reg.observe(name, value)


def counter_value(name: str) -> float:
    """Current value of a counter on the active registry (0.0 when no
    recorder is active) — lets ratio gauges like ``serve.shed_ratio`` be
    derived from their component counters at the increment site."""
    reg = _active_registry()
    return reg.counter_value(name) if reg is not None else 0.0
