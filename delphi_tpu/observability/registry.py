"""Process-wide metrics registry: counters, gauges, histograms.

The registry itself is a plain locked dict-of-numbers container owned by the
active :class:`~delphi_tpu.observability.spans.RunRecorder`. The module-level
helpers (:func:`counter_inc` & co.) are what instrumented pipeline code calls;
they no-op with a single global ``is None`` check when no run recorder is
active (i.e. neither ``DELPHI_METRICS_PATH`` nor ``repair.metrics.path`` is
set), so always-on instrumentation costs nothing on the default path.
"""

import threading
from typing import Any, Dict, List, Optional, Union

Number = Union[int, float]

# How many raw observations a histogram keeps for percentile estimation.
# Beyond this the count/sum/min/max stay exact but p50/p95 are computed from
# the first _HIST_SAMPLE_CAP values only.
_HIST_SAMPLE_CAP = 512


class _Histogram:
    __slots__ = ("count", "total", "min", "max", "samples")

    def __init__(self) -> None:
        self.count = 0
        self.total = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self.samples: List[float] = []

    def observe(self, value: float) -> None:
        self.count += 1
        self.total += value
        self.min = value if self.min is None else min(self.min, value)
        self.max = value if self.max is None else max(self.max, value)
        if len(self.samples) < _HIST_SAMPLE_CAP:
            self.samples.append(value)

    def summary(self) -> Dict[str, Any]:
        s = sorted(self.samples)

        def pct(q: float) -> Optional[float]:
            if not s:
                return None
            return s[min(len(s) - 1, int(q * len(s)))]

        return {
            "count": self.count,
            "sum": self.total,
            "min": self.min,
            "max": self.max,
            "mean": (self.total / self.count) if self.count else None,
            "p50": pct(0.50),
            "p95": pct(0.95),
        }


class MetricsRegistry:
    """Thread-safe named counters/gauges/histograms with a JSON snapshot."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, float] = {}
        self._histograms: Dict[str, _Histogram] = {}

    def inc(self, name: str, value: Number = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + value

    def set_gauge(self, name: str, value: Number) -> None:
        with self._lock:
            self._gauges[name] = value

    def max_gauge(self, name: str, value: Number) -> None:
        """Keeps the maximum value seen — e.g. peak per-chunk row counts."""
        with self._lock:
            prev = self._gauges.get(name)
            self._gauges[name] = value if prev is None else max(prev, value)

    def observe(self, name: str, value: Number) -> None:
        with self._lock:
            hist = self._histograms.get(name)
            if hist is None:
                hist = self._histograms[name] = _Histogram()
            hist.observe(float(value))

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "counters": dict(sorted(self._counters.items())),
                "gauges": dict(sorted(self._gauges.items())),
                "histograms": {k: v.summary() for k, v
                               in sorted(self._histograms.items())},
            }


# Cached reference to the spans module, resolved on first use. Importing
# lazily avoids a registry<->spans import cycle; caching keeps the disabled
# fast path to an attribute load + `is None` check.
_spans_mod = None


def _active_registry() -> Optional[MetricsRegistry]:
    global _spans_mod
    if _spans_mod is None:
        from delphi_tpu.observability import spans
        _spans_mod = spans

    rec = _spans_mod._current
    return rec.registry if rec is not None else None


def counter_inc(name: str, value: Number = 1) -> None:
    reg = _active_registry()
    if reg is not None:
        reg.inc(name, value)


def gauge_set(name: str, value: Number) -> None:
    reg = _active_registry()
    if reg is not None:
        reg.set_gauge(name, value)


def gauge_max(name: str, value: Number) -> None:
    reg = _active_registry()
    if reg is not None:
        reg.max_gauge(name, value)


def histogram_observe(name: str, value: Number) -> None:
    reg = _active_registry()
    if reg is not None:
        reg.observe(name, value)
