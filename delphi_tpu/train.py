"""Repair-model training: option registry, model dispatch, class rebalancing.

API-compatible port of the reference's `python/repair/train.py` surface
(`build_model`, `rebalance_training_data`, `compute_class_nrow_stdv`,
`train_option_keys`): the LightGBM + hyperopt stack is replaced by jitted JAX
models (see :mod:`delphi_tpu.models`). The `model.lgb.*` / `model.cv.*` /
`model.hp.*` option keys are preserved so reference configurations keep
validating; the applicable ones map onto the JAX trainers
(learning_rate -> optimizer lr, n_estimators -> boosting rounds / step budget,
max_depth -> tree depth).
"""

from collections import namedtuple
from typing import Any, Dict, Optional, Tuple

import numpy as np
import pandas as pd

from delphi_tpu.observability import (active_ledger, counter_inc,
                                      histogram_observe)
from delphi_tpu.utils import elapsed_time, get_option_value, setup_logger

_logger = setup_logger()

_option = namedtuple("_option", "key default_value type_class validator err_msg")

_opt_boosting_type = \
    _option("model.lgb.boosting_type", "gbdt", str,
            lambda v: v in ["gbdt", "dart", "goss", "rf"],
            "`{}` should be in ['gbdt', 'dart', 'goss', 'rf']")
_opt_class_weight = \
    _option("model.lgb.class_weight", "balanced", str, None, None)
_opt_learning_rate = \
    _option("model.lgb.learning_rate", 0.01, float,
            lambda v: v > 0.0, "`{}` should be positive")
_opt_max_depth = \
    _option("model.lgb.max_depth", 7, int, None, None)
_opt_max_bin = \
    _option("model.lgb.max_bin", 255, int, None, None)
_opt_reg_alpha = \
    _option("model.lgb.reg_alpha", 0.0, float,
            lambda v: v >= 0.0, "`{}` should be greater than or equal to 0.0")
_opt_min_split_gain = \
    _option("model.lgb.min_split_gain", 0.0, float,
            lambda v: v >= 0.0, "`{}` should be greater than or equal to 0.0")
_opt_n_estimators = \
    _option("model.lgb.n_estimators", 300, int,
            lambda v: v > 0, "`{}` should be positive")
_opt_importance_type = \
    _option("model.lgb.importance_type", "gain", str,
            lambda v: v in ["split", "gain"], "`{}` should be in ['split', 'gain']")
_opt_n_splits = \
    _option("model.cv.n_splits", 3, int,
            lambda v: v >= 3, "`{}` should be greater than 2")
_opt_timeout = \
    _option("model.hp.timeout", 0, int, None, None)
_opt_max_evals = \
    _option("model.hp.max_evals", 100000000, int,
            lambda v: v > 0, "`{}` should be positive")
_opt_no_progress_loss = \
    _option("model.hp.no_progress_loss", 50, int,
            lambda v: v > 0, "`{}` should be positive")
_opt_stop_score = \
    _option("model.hp.stop_score", 0.995, float,
            lambda v: 0.0 < v <= 1.0, "`{}` should be in (0.0, 1.0]")

train_option_keys = [
    _opt_boosting_type.key,
    _opt_class_weight.key,
    _opt_learning_rate.key,
    _opt_max_depth.key,
    _opt_max_bin.key,
    _opt_reg_alpha.key,
    _opt_min_split_gain.key,
    _opt_n_estimators.key,
    _opt_importance_type.key,
    _opt_n_splits.key,
    _opt_timeout.key,
    _opt_max_evals.key,
    _opt_no_progress_loss.key,
    _opt_stop_score.key,
]


def _f1_macro(y_true: np.ndarray, y_pred: np.ndarray) -> float:
    from delphi_tpu.models.encoding import f1_macro
    return f1_macro(y_true, y_pred)


def _cv_score(make_model, X: np.ndarray, y: pd.Series, is_discrete: bool,
              n_splits: int) -> float:
    """K-fold CV score: f1_macro for classifiers, -MSE for regressors —
    the same scorers the reference feeds hyperopt (train.py:158)."""
    y_arr = np.asarray(y)
    n = len(y_arr)
    n_splits = max(2, min(n_splits, n))
    rng = np.random.RandomState(42)
    order = rng.permutation(n)
    folds = np.array_split(order, n_splits)
    scores = []
    for i, test_idx in enumerate(folds):
        train_idx = np.concatenate([f for j, f in enumerate(folds) if j != i])
        if len(train_idx) == 0 or len(test_idx) == 0:
            continue
        if is_discrete and len(np.unique(y_arr[train_idx])) < 2:
            continue
        try:
            m = make_model()
            m.fit(X[train_idx], pd.Series(y_arr[train_idx]))
            pred = np.asarray(m.predict(X[test_idx]))
            if is_discrete:
                scores.append(_f1_macro(y_arr[test_idx].astype(str),
                                        pred.astype(str)))
            else:
                truth = y_arr[test_idx].astype(np.float64)
                scores.append(-float(((pred.astype(np.float64) - truth) ** 2).mean()))
        except Exception as e:
            _logger.warning(f"{e.__class__}: {e}")
            scores.append(-np.inf)
    return float(np.mean(scores)) if scores else -np.inf


# Candidate hyperparameter grid evaluated by CV — the compact stand-in for the
# reference's hyperopt TPE search (train.py:148-209); shallow, strongly
# regularized configs win on small tables, deeper ones on large.
# The search grid spans the same axes the reference's TPE space explores
# (reference train.py:148-156: reg_lambda loguniform(-2,3), min_child_weight
# loguniform(-3,1), tree-size knobs) but as a fixed grid the batched CV can
# evaluate in one vmapped launch per (depth, rounds) shape group — configs
# within a group add vmap width, not compiles. Ordered so that
# `model.hp.max_evals` prefix-slicing keeps the strongest defaults first.
_GBDT_GRID = [
    dict(max_depth=3, reg_lambda=3.0, learning_rate=0.05, n_estimators=300),
    dict(max_depth=3, reg_lambda=1.0, learning_rate=0.1, n_estimators=200),
    dict(max_depth=5, reg_lambda=1.0, learning_rate=0.1, n_estimators=200),
    dict(max_depth=5, reg_lambda=1.0, learning_rate=0.1, n_estimators=200,
         min_child_weight=5.0),
    dict(max_depth=3, reg_lambda=0.15, learning_rate=0.1, n_estimators=200),
    dict(max_depth=3, reg_lambda=10.0, learning_rate=0.05, n_estimators=200),
    dict(max_depth=3, reg_lambda=1.0, learning_rate=0.2, n_estimators=200,
         min_child_weight=0.05),
    dict(max_depth=5, reg_lambda=5.0, learning_rate=0.05, n_estimators=200),
    dict(max_depth=5, reg_lambda=0.15, learning_rate=0.1, n_estimators=200,
         min_child_weight=0.5),
    dict(max_depth=5, reg_lambda=1.0, learning_rate=0.2, n_estimators=200,
         min_child_weight=2.5),
]


def _refine_candidates(cfg: dict, seen: list, scale: int = 1) -> list:
    """Local perturbations of the winning grid config along the continuous
    axes the reference's TPE space explores (reg_lambda, learning_rate,
    min_child_weight — reference train.py:148-156), at the SAME tree depth
    and round count so the whole refined set rides one vmapped CV launch.
    ``scale`` widens the step factors (no-progress rounds look further out
    instead of re-proposing the same neighborhood)."""
    base_rl = float(cfg.get("reg_lambda", 1.0))
    base_lr = float(cfg.get("learning_rate", 0.1))
    base_mcw = float(cfg.get("min_child_weight", 1.0))
    f_rl, f_lr, f_mcw = 3.0 ** scale, 2.0 ** scale, 3.0 ** scale
    out = []
    for rl in (base_rl / f_rl, base_rl * f_rl):
        out.append({**cfg, "reg_lambda": rl})
    for lr in (base_lr / f_lr, min(0.5, base_lr * f_lr)):
        out.append({**cfg, "learning_rate": lr})
    for mcw in (base_mcw / f_mcw, base_mcw * f_mcw):
        out.append({**cfg, "min_child_weight": mcw})
    uniq = []
    for c in out:
        if c not in seen and c not in uniq and c != cfg:
            uniq.append(c)
    return uniq


def _refine_best_config(X, y, is_discrete, best_cfg, best_score, best_rounds,
                        grid, n_splits, class_weight, template, deadline,
                        no_progress_evals, explicit, good_enough=0.995):
    """Adaptive second phase of the hyperparameter search, honoring
    `model.hp.no_progress_loss` (the reference's hyperopt early-stop,
    train.py:196): rounds of local refinement around the current best config
    continue until `no_progress_evals` consecutive candidate evaluations
    bring no improvement (each round evaluates ~6 candidates). `deadline`
    (monotonic seconds, or None) bounds the WHOLE search including the base
    grid pass, like the reference's hyperopt timeout. On a CPU backend the
    extra CV launches cost real sequential FLOPs, so refinement there is
    opt-in by setting the option; accelerators refine by default."""
    import time

    from delphi_tpu.models.gbdt import gbdt_cv_grid_search

    if not explicit:
        import jax
        if jax.default_backend() == "cpu":
            return best_cfg, best_score, best_rounds
    if not np.isfinite(best_score):
        return best_cfg, best_score, best_rounds

    max_rounds = 5
    evals_no_progress = 0
    scale = 1
    seen = list(grid)
    for _ in range(max_rounds):
        remaining = None if deadline is None else deadline - time.monotonic()
        if remaining is not None and remaining <= 0:
            break
        candidates = _refine_candidates(best_cfg, seen, scale=scale)
        if not candidates:
            break
        seen.extend(candidates)
        ci, score, rounds, r_timed = gbdt_cv_grid_search(
            X, y, is_discrete, candidates, n_splits, class_weight, template,
            timeout_s=remaining if remaining is not None else 0.0,
            good_enough=good_enough)
        if score <= best_score:
            evals_no_progress += len(candidates)
            if evals_no_progress >= no_progress_evals or scale >= 3:
                break
            scale += 1  # widen the neighborhood instead of re-proposing it
            continue
        evals_no_progress = 0
        scale = 1
        _logger.info(
            f"Refinement improved CV score {best_score:.4f} -> {score:.4f} "
            f"({candidates[ci]})")
        if r_timed and rounds > 0:
            # deadline-truncated search: its round count is where the clock
            # ran out, not a CV-proven early stop — 0 disables the final
            # fit's round truncation (the reference's hyperopt timeout only
            # bounds the search, never the final round budget)
            rounds = 0
        # candidates carry the GRID's round budget (best_cfg is never given
        # the truncated count — a slower-learning candidate must be free to
        # use more rounds than the incumbent's early stop chose); the
        # winner's own CV-proven round count travels alongside
        best_cfg, best_score, best_rounds = dict(candidates[ci]), score, rounds
    return best_cfg, best_score, best_rounds


@elapsed_time  # type: ignore
def _build_jax_model(X: np.ndarray, y: pd.Series, is_discrete: bool, num_class: int,
                     n_jobs: int, opts: Dict[str, str]) -> Tuple[Any, float]:
    def opt(*args):  # type: ignore
        return get_option_value(opts, *args)

    try:
        from delphi_tpu.models.gbdt import (
            GradientBoostedTreesModel, gbdt_cv_grid_search, gbdt_supported)
        n_splits = int(opt(*_opt_n_splits))
        max_evals = int(opt(*_opt_max_evals))
        class_weight = str(opt(*_opt_class_weight))
        from delphi_tpu.models.encoding import OneHotDesign
        if not isinstance(X, OneHotDesign):  # the linear heads take the
            X = np.asarray(X)                # factored design as-is

        if gbdt_supported(is_discrete, num_class):
            def factory(cfg):
                def make():
                    return GradientBoostedTreesModel(
                        is_discrete=is_discrete, num_class=num_class,
                        max_bin=int(opt(*_opt_max_bin)),
                        min_split_gain=float(opt(*_opt_min_split_gain)),
                        class_weight=class_weight, **cfg)
                return make

            # Platform-aware search depth (_trimmed_grid): on an accelerator
            # the extra configs ride the same vmapped launches almost free,
            # but on a CPU host every config costs real sequential FLOPs.
            # Classifiers trim to the strongest config per tree depth —
            # their searches also early-exit on perfect/near-perfect CV F1,
            # and the hospital / flights / adult gates hold at this width.
            # Regressors keep 4: RMSE gates (boston CRIM+RAD) are sensitive
            # to the reg_lambda/min_child_weight axis the 2-config trim
            # would drop, and regression targets are the minority.
            import jax
            grid = _trimmed_grid(is_discrete, num_class, max_evals, opts,
                                 jax.default_backend() == "cpu")
            best_cfg, best_score = grid[0], -np.inf
            if len(grid) > 1 and len(X) >= n_splits * 2:
                # every (config, fold) instance trains in ONE vmapped XLA
                # launch instead of the reference's sequential hyperopt loop
                import time
                template = factory(grid[0])()
                timeout_s = float(opt(*_opt_timeout))
                # one deadline bounds the WHOLE search (base grid +
                # refinement), like the reference's hyperopt timeout
                deadline = time.monotonic() + timeout_s if timeout_s > 0 \
                    else None
                good_enough = float(opt(*_opt_stop_score))
                best_ci, best_score, best_rounds, timed0 = \
                    gbdt_cv_grid_search(
                        X, y, is_discrete, grid, n_splits, class_weight,
                        template, timeout_s=timeout_s,
                        good_enough=good_enough)
                if timed0:
                    # a deadline-truncated search must not shrink the final
                    # fit's round budget (see _refine_best_config)
                    best_rounds = 0
                best_cfg = dict(grid[best_ci])
                if best_score < good_enough:
                    # refinement only for targets the base grid left below
                    # the good-enough bar — same gate as the batched path
                    # (build_models_batched), so the two paths pick
                    # identical configs
                    best_cfg, best_score, best_rounds = _refine_best_config(
                        X, y, is_discrete, best_cfg, best_score, best_rounds,
                        grid, n_splits, class_weight, template, deadline,
                        no_progress_evals=int(opt(*_opt_no_progress_loss)),
                        explicit=_opt_no_progress_loss.key in opts,
                        good_enough=good_enough)
                if best_rounds > 0 and is_discrete:
                    # the final fit trains only as many rounds as CV proved
                    # useful for the WINNING config (LightGBM
                    # early_stopping_rounds semantics, reference
                    # train.py:193-200); applied after refinement so
                    # refinement candidates keep the full round budget.
                    # Classifiers only: their macro-F1 saturates early and
                    # the perfect/good-enough exits make the choice robust,
                    # while regression MSE keeps creeping down with rounds
                    # (truncating measurably worsened the iris example RMSE
                    # vs the reference transcript)
                    best_cfg["n_estimators"] = best_rounds
            model = factory(best_cfg)()
            model.fit(X, y)
            return model, best_score if np.isfinite(best_score) else -model.loss_

        if is_discrete:
            from delphi_tpu.models.linear import LogisticRegressionModel
            model = LogisticRegressionModel()
            model.fit(X, y)
            return model, -model.loss_
        from delphi_tpu.models.linear import MLPRegressorModel
        model = MLPRegressorModel()
        model.fit(X, y)
        return model, -model.loss_
    except Exception as e:
        _logger.warning(f"Failed to build a stat model because: {e}")
        return None, 0.0


def build_model(X: np.ndarray, y: pd.Series, is_discrete: bool, num_class: int,
                n_jobs: int, opts: Dict[str, str]) -> Tuple[Tuple[Any, float], float]:
    """Returns ((model, score), elapsed_seconds); model is None on failure
    (callers substitute PoorModel, reference train.py:227-229)."""
    out = _build_jax_model(X, y, is_discrete, num_class, n_jobs, opts)
    counter_inc("train.model_builds")
    histogram_observe("train.model_build_seconds", out[1])
    return out


def _trimmed_grid(is_discrete: bool, num_class: int, max_evals: int,
                  opts: Dict[str, str], cpu: bool) -> list:
    """The per-target candidate grid `_build_jax_model` would search —
    platform-aware trimming included — factored out so the batched path
    selects identical grids."""
    grid = _GBDT_GRID[: max(1, min(len(_GBDT_GRID), max_evals))]
    if _opt_max_evals.key not in opts and cpu:
        if is_discrete:
            seen_depths: set = set()
            trimmed = []
            for cfg in grid[:4]:
                depth = cfg.get("max_depth", 7)
                if depth not in seen_depths:
                    seen_depths.add(depth)
                    trimmed.append(cfg)
            grid = trimmed
        else:
            grid = grid[:4]
    if is_discrete and num_class > 8:
        # wide multiclass: CV grid search is too costly for the gain
        grid = grid[:1]
    return grid


def _record_model_scores(
        results: Dict[str, Tuple[Tuple[Any, float], float]]) \
        -> Dict[str, Tuple[Tuple[Any, float], float]]:
    """Lands each target's CV score in the provenance ledger (it surfaces
    as ``model_cv_score`` on the attribute's quality scorecard)."""
    led = active_ledger()
    if led is not None:
        for name, ((model, score), _elapsed) in results.items():
            if model is not None:
                led.record_model_score(name, score)
    return results


def build_models_batched(tasks: list, opts: Dict[str, str]) \
        -> Dict[str, Tuple[Tuple[Any, float], float]]:
    """Builds MANY per-attribute repair models with batched device work —
    the TPU-native replacement for the reference's parallel pandas-UDF
    training fan-out (reference model.py:817-926): instead of distributing
    N per-attribute fits over executors, their CV searches stack into
    shared vmapped launches (`gbdt_cv_grid_search_multi`) and their final
    fits advance in shape-grouped batched boosting chunks
    (`gbdt_fit_batch`), so phase 2 issues a handful of device-saturating
    programs instead of N sequential small ones.

    ``tasks``: list of (name, X, y, is_discrete, num_class). Returns
    {name: ((model, score), elapsed_s)}; model None on failure, like
    :func:`build_model`. Non-GBDT targets (wide multiclass -> logistic
    head, linear designs) train per-target via :func:`build_model` —
    their fits are single jitted launches already."""
    import time

    t0 = time.time()
    results: Dict[str, Tuple[Tuple[Any, float], float]] = {}
    gbdt_tasks = []
    try:
        from delphi_tpu.models.encoding import OneHotDesign
        from delphi_tpu.models.gbdt import (
            GradientBoostedTreesModel, _cv_prepare_target,
            gbdt_cv_grid_search_multi, gbdt_fit_batch, gbdt_supported)
        for task in tasks:
            name, X, y, is_discrete, num_class = task
            if gbdt_supported(is_discrete, num_class) \
                    and not isinstance(X, OneHotDesign):
                gbdt_tasks.append(task)
            else:
                results[name] = build_model(
                    X, y, is_discrete, num_class, -1, opts)
        if not gbdt_tasks:
            return _record_model_scores(results)

        def opt(*args):  # type: ignore
            return get_option_value(opts, *args)

        counter_inc("train.batched_gbdt_targets", len(gbdt_tasks))
        n_splits = int(opt(*_opt_n_splits))
        max_evals = int(opt(*_opt_max_evals))
        class_weight = str(opt(*_opt_class_weight))
        good_enough = float(opt(*_opt_stop_score))
        timeout_s = float(opt(*_opt_timeout))
        # model.hp.timeout is a PER-TARGET budget (each sequential search
        # gets its own window, reference train.py:196); the batched path
        # pools the same total so later cv_sets aren't starved by earlier
        # ones consuming a single per-target window
        deadline = time.monotonic() + timeout_s * len(gbdt_tasks) \
            if timeout_s > 0 else None

        import jax

        from delphi_tpu.parallel.mesh import get_active_mesh
        cpu = jax.default_backend() == "cpu"
        mesh = get_active_mesh()

        def factory(cfg: dict, is_discrete: bool, num_class: int) \
                -> GradientBoostedTreesModel:
            return GradientBoostedTreesModel(
                is_discrete=is_discrete, num_class=num_class,
                max_bin=int(opt(*_opt_max_bin)),
                min_split_gain=float(opt(*_opt_min_split_gain)),
                class_weight=class_weight, **cfg)

        # tasks sharing a candidate grid share one multi-target CV search;
        # single-config grids (wide multiclass) skip CV entirely
        chosen: Dict[int, Tuple[dict, float, int, list]] = {}
        templates: Dict[int, Any] = {}
        cv_sets: Dict[tuple, list] = {}
        for ti, (name, X, y, is_discrete, num_class) in enumerate(gbdt_tasks):
            grid = _trimmed_grid(is_discrete, num_class, max_evals, opts, cpu)
            chosen[ti] = (dict(grid[0]), -np.inf, 0, grid)
            if len(grid) > 1 and len(X) >= n_splits * 2:
                sig = tuple(tuple(sorted(c.items())) for c in grid)
                cv_sets.setdefault(sig, []).append(ti)

        def _prep_cv_set(tis: list) -> list:
            # host featurization of one CV set: fold binning, padding,
            # scoring constants (pandas/numpy only — device-free, so it can
            # run on the pipeline's prepare thread)
            grid = chosen[tis[0]][3]
            preps = []
            for ti in tis:
                name, X, y, is_discrete, num_class = gbdt_tasks[ti]
                tmpl = factory(dict(grid[0]), is_discrete, num_class)
                templates[ti] = tmpl
                try:
                    preps.append(_cv_prepare_target(
                        X, y, is_discrete, n_splits, class_weight, tmpl,
                        mesh))
                except Exception as e:
                    _logger.warning(f"{e.__class__}: {e}")
                    preps.append(None)
            return preps

        def _search_cv_set(tis: list, preps: list) -> None:
            grid = chosen[tis[0]][3]
            remaining = 0.0 if deadline is None \
                else max(deadline - time.monotonic(), 1e-3)
            res = gbdt_cv_grid_search_multi(
                preps, grid, timeout_s=remaining, good_enough=good_enough)
            for ti, (ci, score, rounds, timed) in zip(tis, res):
                if timed:
                    rounds = 0  # not CV-proven: keep the full round budget
                chosen[ti] = (dict(grid[ci]), score, rounds, grid)

        # featurization of CV set k+1 overlaps the device search of set k
        from delphi_tpu.parallel.pipeline import run_pipelined
        run_pipelined(list(cv_sets.values()), _prep_cv_set, _search_cv_set)

        # local refinement stays per-target (candidate neighborhoods
        # diverge), but only for targets the base grid left below the
        # good-enough bar — the ones refinement can actually help
        explicit = _opt_no_progress_loss.key in opts
        for ti in list(templates):
            name, X, y, is_discrete, num_class = gbdt_tasks[ti]
            cfg, score, rounds, grid = chosen[ti]
            if np.isfinite(score) and score < good_enough:
                cfg, score, rounds = _refine_best_config(
                    X, y, is_discrete, cfg, score, rounds, grid, n_splits,
                    class_weight, templates[ti], deadline,
                    no_progress_evals=int(opt(*_opt_no_progress_loss)),
                    explicit=explicit, good_enough=good_enough)
                chosen[ti] = (cfg, score, rounds, grid)

        entries = []
        finals: Dict[int, Tuple[Any, float]] = {}
        for ti, (name, X, y, is_discrete, num_class) in enumerate(gbdt_tasks):
            cfg, score, rounds, grid = chosen[ti]
            cfg = dict(cfg)
            if rounds > 0 and is_discrete:
                # CV-proven early stop sizes the final fit (classifiers
                # only — see _build_jax_model)
                cfg["n_estimators"] = rounds
            m = factory(cfg, is_discrete, num_class)
            finals[ti] = (m, score)
            entries.append((m, X, y))
        try:
            gbdt_fit_batch(entries)
        except Exception as e:
            _logger.warning(
                f"Batched fit failed ({e.__class__}: {e}); falling back to "
                "per-target fits")
            for mi, (m, X, y) in enumerate(entries):
                try:
                    m.fit(X, y)
                except Exception as e2:
                    _logger.warning(f"{e2.__class__}: {e2}")
                    finals[mi] = (None, 0.0)

        elapsed_each = (time.time() - t0) / max(len(gbdt_tasks), 1)
        for ti, (name, X, y, is_discrete, num_class) in enumerate(gbdt_tasks):
            m, score = finals[ti]
            score = score if m is not None and np.isfinite(score) \
                else (-m.loss_ if m is not None else 0.0)
            results[name] = ((m, score), elapsed_each)
        return _record_model_scores(results)
    except Exception as e:
        # total batched-path failure: every unresolved task falls back to
        # the sequential builder (never silently drop a target)
        _logger.warning(
            f"Batched training failed ({e.__class__}: {e}); falling back "
            "to sequential per-target training")
        for task in tasks:
            name, X, y, is_discrete, num_class = task
            if name not in results:
                results[name] = build_model(
                    X, y, is_discrete, num_class, -1, opts)
        return _record_model_scores(results)


def compute_class_nrow_stdv(y: pd.Series, is_discrete: bool) -> Optional[float]:
    if not is_discrete:
        return None
    counts = pd.Series(np.asarray(y)).value_counts(dropna=False)
    return float(np.std(counts.to_numpy()))


def rebalance_training_data(X: pd.DataFrame, y: pd.Series, target: str) \
        -> Tuple[pd.DataFrame, pd.Series]:
    """Class rebalancing toward the median class size: oversample minority
    classes (with replacement; a native stand-in for SMOTEN) and undersample
    majority classes (reference train.py:242-293; imblearn is not available
    in this environment)."""
    rng = np.random.RandomState(42)
    prev_nrows = len(X)
    prev_stdv = compute_class_nrow_stdv(y, is_discrete=True)

    y_arr = pd.Series(np.asarray(y)).reset_index(drop=True)
    is_frame = isinstance(X, pd.DataFrame)
    if is_frame:
        X = X.reset_index(drop=True)
    hist = y_arr.value_counts()
    median = int(np.median(hist.to_numpy()))

    idx_parts = []
    for cls, count in hist.items():
        cls_idx = np.nonzero((y_arr == cls).to_numpy())[0]
        if count < median:
            extra = rng.choice(cls_idx, size=median - count, replace=True)
            idx_parts.append(np.concatenate([cls_idx, extra]))
        elif count > median:
            idx_parts.append(rng.choice(cls_idx, size=median, replace=False))
        else:
            idx_parts.append(cls_idx)

    idx = np.concatenate(idx_parts) if idx_parts else np.arange(len(X))
    Xb = X.iloc[idx].reset_index(drop=True) if is_frame else np.asarray(X)[idx]
    yb = y_arr.iloc[idx].reset_index(drop=True)
    _logger.info(
        "Rebalanced training data (y={}, median={}): #rows={}(stdv={}) -> "
        "#rows={}(stdv={})".format(
            target, median, prev_nrows, prev_stdv, len(Xb),
            compute_class_nrow_stdv(yb, is_discrete=True)))
    return Xb, yb
