"""Cluster-wise blocking preprocess for hospital
(reference resources/examples/hospital-preprocess-blocking.py): the
reference builds 2-gram bag-of-words features with Spark ML
(NGram -> CountVectorizer -> PCA -> BisectingKMeans, k=3) so cleaning can
run per row-cluster. Here the same blocking runs through the TPU-native
path: hashed q-gram featurization (`delphi_tpu.ops.cluster.qgram_features`,
the native C++ featurizer when built) and jitted JAX k-means — also exposed
as `delphi.misc.splitInputTable()` (RepairMiscApi.scala:78-153 parity).

    python examples/hospital_preprocess_blocking.py [path-to-testdata]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pandas as pd

from delphi_tpu import delphi

TESTDATA = sys.argv[1] if len(sys.argv) > 1 else "/root/reference/testdata"

hospital = pd.read_csv(f"{TESTDATA}/hospital.csv", dtype=str).head(100)
delphi.register_table("hospital", hospital)

split = delphi.misc.options({
    "table_name": "hospital", "row_id": "tid", "k": "3", "q": "2",
}).splitInputTable()
print(split.head())
print("cluster sizes:", split["k"].value_counts().to_dict())

# Per-cluster repair runs over disjoint row groups, as the reference intends.
for k, group in split.groupby("k"):
    sub = hospital[hospital["tid"].isin(group["tid"])].reset_index(drop=True)
    print(f"cluster {k}: {len(sub)} rows ready for an independent repair run")
