"""Raha movies repair with ground-truth error cells
(reference resources/examples/movies.py): another known-failure dataset —
the reference transcript records P/R/F1 = 0.0 (long free-text attributes).
Uses discreteThreshold=600 and the reference's relaxed search budget.

    python examples/movies.py [path-to-raha-testdata]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pandas as pd

from delphi_tpu import delphi

TESTDATA = sys.argv[1] if len(sys.argv) > 1 else "/root/reference/testdata/raha"

if not os.path.exists(f"{TESTDATA}/movies.csv"):
    print(f"SKIP: {TESTDATA}/movies.csv not found (the raha movies dataset "
          "is not bundled in this checkout; pass its directory as argv[1])")
    sys.exit(0)

movies = pd.read_csv(f"{TESTDATA}/movies.csv", dtype=str, escapechar="\\")
clean = pd.read_csv(f"{TESTDATA}/movies_clean.csv", dtype=str, escapechar="\\")
delphi.register_table("movies", movies)

flat = delphi.misc.options({"table_name": "movies", "row_id": "id"}).flatten()
merged = flat.merge(clean, on=["id", "attribute"], how="inner")
neq = ~((merged["value"] == merged["correct_val"])
        | (merged["value"].isna() & merged["correct_val"].isna()))
delphi.register_table(
    "error_cells_ground_truth",
    merged[neq][["id", "attribute"]].reset_index(drop=True))

repaired_df = delphi.repair \
    .setDbName("default") \
    .setTableName("movies") \
    .setRowId("id") \
    .setErrorCells("error_cells_ground_truth") \
    .setDiscreteThreshold(600) \
    .run()

pdf = repaired_df.merge(clean, on=["id", "attribute"], how="inner")
rdf = delphi.table("error_cells_ground_truth") \
    .merge(repaired_df, on=["id", "attribute"], how="left") \
    .merge(clean, on=["id", "attribute"], how="left")

nse = lambda a, b: (a == b) | (a.isna() & b.isna())
precision = float(nse(pdf["repaired"], pdf["correct_val"]).mean()) if len(pdf) else 0.0
recall = float(nse(rdf["repaired"], rdf["correct_val"]).mean())
f1 = (2.0 * precision * recall) / (precision + recall + 0.0001)
print(f"Precision={precision} Recall={recall} F1={f1}")
