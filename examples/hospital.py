"""End-to-end hospital repair with detectors + rules
(reference resources/examples/hospital.py): detect errors with NULL + denial
constraints, repair with FD rules + stat models, score against the ground
truth.

    python examples/hospital.py [path-to-testdata]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pandas as pd

from delphi_tpu import delphi, ConstraintErrorDetector, NullErrorDetector

TESTDATA = sys.argv[1] if len(sys.argv) > 1 else "/root/reference/bin/testdata"

hospital = pd.read_csv(f"{TESTDATA}/hospital.csv", dtype=str)
clean = pd.read_csv(f"{TESTDATA}/hospital_clean.csv", dtype=str)
delphi.register_table("hospital", hospital)

repaired_df = delphi.repair \
    .setInput("hospital") \
    .setRowId("tid") \
    .setErrorDetectors([
        NullErrorDetector(),
        ConstraintErrorDetector(constraint_path=f"{TESTDATA}/hospital_constraints.txt"),
    ]) \
    .setDiscreteThreshold(100) \
    .setRepairByRules(True) \
    .run()

# Precision: correct repairs / repairs performed; recall: correct / all errors.
# `Score` is excluded from scoring exactly like the reference example
# (resources/examples/hospital.py: `attribute != 'Score'`) — it is a
# free-numeric column no categorical model can reconstruct.
pdf = repaired_df.merge(clean[clean["attribute"] != "Score"],
                        on=["tid", "attribute"], how="inner")
truth = pd.read_csv(f"{TESTDATA}/hospital_error_cells.csv", dtype=str)
rdf = truth[truth["attribute"] != "Score"] \
    .merge(repaired_df, on=["tid", "attribute"], how="left")

nse = lambda a, b: (a == b) | (a.isna() & b.isna())
precision = float(nse(pdf["repaired"], pdf["correct_val"]).mean())
recall = float(nse(rdf["repaired"], rdf["correct_val"]).mean())
f1 = 2 * precision * recall / (precision + recall)
print(f"Precision={precision} Recall={recall} F1={f1}")
