"""Raha tax repair with ground-truth error cells and a target-attr subset
(reference resources/examples/tax.py): 200k rows; only `state`,
`marital_status`, `has_child` are repaired (discreteThreshold=300). The
reference transcript records P/R/F1 = 0.9998 on those targets.

    python examples/tax.py [path-to-raha-testdata]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pandas as pd

from delphi_tpu import delphi

TESTDATA = sys.argv[1] if len(sys.argv) > 1 else "/root/reference/testdata/raha"
TARGETS = ["state", "marital_status", "has_child"]

if not os.path.exists(f"{TESTDATA}/tax.csv"):
    print(f"SKIP: {TESTDATA}/tax.csv not found (the raha tax dataset is not "
          "bundled in this checkout; pass its directory as argv[1])")
    sys.exit(0)

tax = pd.read_csv(f"{TESTDATA}/tax.csv", dtype=str, escapechar="\\")
clean = pd.read_csv(f"{TESTDATA}/tax_clean.csv", dtype=str, escapechar="\\")
delphi.register_table("tax", tax)

# Column stats, as the reference example shows via misc.describe().
print(delphi.misc.options({"table_name": "tax"}).describe())

flat = delphi.misc.options({"table_name": "tax", "row_id": "tid"}).flatten()
merged = flat.merge(clean, on=["tid", "attribute"], how="inner")
neq = ~((merged["value"] == merged["correct_val"])
        | (merged["value"].isna() & merged["correct_val"].isna()))
delphi.register_table(
    "error_cells_ground_truth",
    merged[neq][["tid", "attribute"]].reset_index(drop=True))

repaired_df = delphi.repair \
    .setDbName("default") \
    .setTableName("tax") \
    .setRowId("tid") \
    .setErrorCells("error_cells_ground_truth") \
    .setTargets(TARGETS) \
    .setDiscreteThreshold(300) \
    .run()

pdf = repaired_df.merge(clean, on=["tid", "attribute"], how="inner")
gt = delphi.table("error_cells_ground_truth")
rdf = gt[gt["attribute"].isin(TARGETS)] \
    .merge(repaired_df, on=["tid", "attribute"], how="left") \
    .merge(clean, on=["tid", "attribute"], how="left")

nse = lambda a, b: (a == b) | (a.isna() & b.isna())
precision = float(nse(pdf["repaired"], pdf["correct_val"]).mean()) if len(pdf) else 0.0
recall = float(nse(rdf["repaired"], rdf["correct_val"]).mean())
f1 = (2.0 * precision * recall) / (precision + recall + 1e-9)
print(f"Precision={precision} Recall={recall} F1={f1}")
