"""Repair the adult fixture's NULL cells (reference resources/examples/adult.py).

    python examples/adult.py [path-to-testdata]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pandas as pd

from delphi_tpu import delphi, ConstraintErrorDetector, NullErrorDetector

TESTDATA = sys.argv[1] if len(sys.argv) > 1 else "/root/reference/testdata"

delphi.register_table("adult", pd.read_csv(f"{TESTDATA}/adult.csv"))

repaired_df = delphi.repair \
    .setInput("adult") \
    .setRowId("tid") \
    .setErrorDetectors([
        NullErrorDetector(),
        ConstraintErrorDetector(constraint_path=f"{TESTDATA}/adult_constraints.txt"),
    ]) \
    .run()

print(repaired_df.to_string(index=False))
