"""Tour of every built-in error detector
(reference resources/examples/error-detectors.py): each detector runs in
`detect_errors_only` mode and prints the first few detected cells.

    python examples/error_detectors.py [path-to-testdata]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pandas as pd

from delphi_tpu import delphi
from delphi_tpu.errors import (
    ConstraintErrorDetector,
    DomainValues,
    GaussianOutlierErrorDetector,
    LOFOutlierErrorDetector,
    NullErrorDetector,
    RegExErrorDetector,
    ScikitLearnBackedErrorDetector,
)

TESTDATA = sys.argv[1] if len(sys.argv) > 1 else "/root/reference/testdata"

delphi.register_table("adult", pd.read_csv(f"{TESTDATA}/adult.csv", dtype=str))
delphi.register_table("hospital", pd.read_csv(f"{TESTDATA}/hospital.csv", dtype=str))

boston = pd.read_csv(f"{TESTDATA}/boston.csv", dtype=str)
boston["tid"] = boston["tid"].astype(int)
for c in ["CRIM", "RM", "DIS", "B", "LSTAT"]:
    boston[c] = boston[c].astype(float)
for c in ["ZN", "TAX"]:
    boston[c] = boston[c].astype("Int64")
delphi.register_table("boston", boston)


def show(title, df):
    print(f"--- {title}: {len(df)} cells")
    print(df.head(3).to_string(index=False))


show("NullErrorDetector", delphi.repair
     .setTableName("hospital").setRowId("tid")
     .setErrorDetectors([NullErrorDetector()])
     .run(detect_errors_only=True))

show("DomainValues", delphi.repair
     .setTableName("adult").setRowId("tid")
     .setErrorDetectors([DomainValues(attr="Sex", values=["Male", "Female"])])
     .run(detect_errors_only=True))

show("DomainValues(autofill)", delphi.repair
     .setTableName("hospital").setRowId("tid")
     .setErrorDetectors([
         DomainValues(attr=c, autofill=True, min_count_thres=12)
         for c in ["MeasureCode", "ZipCode", "City"]])
     .run(detect_errors_only=True))

show("RegExErrorDetector", delphi.repair
     .setTableName("hospital").setRowId("tid")
     .setErrorDetectors([RegExErrorDetector(attr="ZipCode", regex="\\d\\d\\d\\d\\d")])
     .run(detect_errors_only=True))

targets = ["City", "HospitalName", "Address1", "CountyName"]
show("ConstraintErrorDetector(path)", delphi.repair
     .setTableName("hospital").setRowId("tid").setTargets(targets)
     .setErrorDetectors([ConstraintErrorDetector(
         constraint_path=f"{TESTDATA}/hospital_constraints.txt")])
     .run(detect_errors_only=True))

show("ConstraintErrorDetector(simple)", delphi.repair
     .setTableName("hospital").setRowId("tid").setTargets(targets)
     .setErrorDetectors([ConstraintErrorDetector(
         constraints="City->CountyName;HospitalName->Address1")])
     .run(detect_errors_only=True))

show("GaussianOutlierErrorDetector", delphi.repair
     .setTableName("boston").setRowId("tid")
     .setErrorDetectors([GaussianOutlierErrorDetector(approx_enabled=False)])
     .run(detect_errors_only=True))

show("LOFOutlierErrorDetector", delphi.repair
     .setTableName("boston").setRowId("tid")
     .setErrorDetectors([LOFOutlierErrorDetector()])
     .run(detect_errors_only=True))

try:
    from sklearn.neighbors import LocalOutlierFactor

    show("ScikitLearnBackedErrorDetector", delphi.repair
         .setTableName("boston").setRowId("tid")
         .setErrorDetectors([ScikitLearnBackedErrorDetector(
             lambda: LocalOutlierFactor(novelty=False))])
         .run(detect_errors_only=True))
except ImportError:
    print("--- ScikitLearnBackedErrorDetector: sklearn not available, skipped")
