"""Raha rayyan repair with ground-truth error cells
(reference resources/examples/rayyan.py): a known-failure dataset — the
reference transcript records P/R/F1 = 0.0, and the diagnosis printed at the
end of this run shows WHY no value-prediction method can do better here:
the benchmark's ground truth itself is broken or out of reach.

Decomposition of the 1,822 ground-truth "error" cells (computed below):
* ~909 author_list cells: rayyan_clean.csv holds TRUNCATED prefixes of the
  (actually correct) dirty values — `"{""A. G. Parks""` with the rest of
  the list lost to naive comma-splitting when the truth file was built.
* ~722 article_jcreated_at cells: the "correct" dates are a mechanical
  field permutation of the dirty dates with inconsistent zero-padding
  ('4/2/15' -> '2/15/04' but '12/1/06' -> '1/6/12'); only ~13 of them even
  appear anywhere in the dirty column.
* ~70 article_jissue/jvolumn cells: truth is the '-1' missing-value
  sentinel, which occurs ZERO times in the dirty table — no data-driven
  method can emit a value the data never exhibits.
* Remaining ~121 cells: free-text/title variants whose truth is likewise
  absent from the dirty table's vocabulary.

Net: only 19 of the 1,822 truths occur anywhere in the dirty table, so an
ORACLE restricted to values observable in the table tops out at recall
1.0% (F1 ~ 2%); the 0.0 is a property of this benchmark's corrupt ground
truth, not of the repair stack.

    python examples/rayyan.py [path-to-raha-testdata]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pandas as pd

from delphi_tpu import delphi

TESTDATA = sys.argv[1] if len(sys.argv) > 1 else "/root/reference/testdata/raha"

# The clean file carries Spark-style backslash-escaped quotes.
rayyan = pd.read_csv(f"{TESTDATA}/rayyan.csv", dtype=str, escapechar="\\")
clean = pd.read_csv(f"{TESTDATA}/rayyan_clean.csv", dtype=str, escapechar="\\")
delphi.register_table("rayyan", rayyan)

flat = delphi.misc.options({"table_name": "rayyan", "row_id": "id"}).flatten()
merged = flat.merge(clean, on=["id", "attribute"], how="inner")
neq = ~((merged["value"] == merged["correct_val"])
        | (merged["value"].isna() & merged["correct_val"].isna()))
delphi.register_table(
    "error_cells_ground_truth",
    merged[neq][["id", "attribute"]].reset_index(drop=True))

repaired_df = delphi.repair \
    .setTableName("rayyan") \
    .setRowId("id") \
    .setErrorCells("error_cells_ground_truth") \
    .setDiscreteThreshold(400) \
    .run()

pdf = repaired_df.merge(clean, on=["id", "attribute"], how="inner")
rdf = delphi.table("error_cells_ground_truth") \
    .merge(repaired_df, on=["id", "attribute"], how="left") \
    .merge(clean, on=["id", "attribute"], how="left")

nse = lambda a, b: (a == b) | (a.isna() & b.isna())
precision = float(nse(pdf["repaired"], pdf["correct_val"]).mean()) if len(pdf) else 0.0
recall = float(nse(rdf["repaired"], rdf["correct_val"]).mean())
f1 = 2 * precision * recall / (precision + recall + 1e-4)
print(f"Precision={precision} Recall={recall} F1={f1}")

# -- why 0.0 is the benchmark's ceiling, not the model's ---------------------
err = merged[neq]


def _unescape(s):
    return s.replace('\\"', '"').replace('""', '"').strip('"') \
        if isinstance(s, str) else s


trunc = sum(
    1 for v, c in zip(err["value"], err["correct_val"])
    if isinstance(v, str) and isinstance(c, str)
    and (_unescape(v) == _unescape(c)
         or (len(_unescape(c)) > 3
             and _unescape(v).startswith(_unescape(c).rstrip('.')))))
in_vocab = 0
for attr, group in err.groupby("attribute"):
    vocab = set(rayyan[attr].dropna())
    in_vocab += sum(1 for c in group["correct_val"] if c in vocab)
sentinel = int((err["correct_val"] == "-1").sum())
print(f"Diagnosis: {len(err)} ground-truth error cells — "
      f"{trunc} have truncated/mangled truth (truth is a broken copy of the "
      f"already-correct value), {sentinel} expect the '-1' sentinel that "
      f"never occurs in the dirty data, and only {in_vocab} truths exist "
      f"anywhere in the dirty table at all (the oracle recall ceiling is "
      f"{in_vocab / max(len(err), 1):.1%}).")
