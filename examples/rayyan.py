"""Raha rayyan repair with ground-truth error cells
(reference resources/examples/rayyan.py): a known-failure dataset — the
reference transcript records P/R/F1 = 0.0 (free-text attributes no
categorical model can repair).

    python examples/rayyan.py [path-to-raha-testdata]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pandas as pd

from delphi_tpu import delphi

TESTDATA = sys.argv[1] if len(sys.argv) > 1 else "/root/reference/testdata/raha"

# The clean file carries Spark-style backslash-escaped quotes.
rayyan = pd.read_csv(f"{TESTDATA}/rayyan.csv", dtype=str, escapechar="\\")
clean = pd.read_csv(f"{TESTDATA}/rayyan_clean.csv", dtype=str, escapechar="\\")
delphi.register_table("rayyan", rayyan)

flat = delphi.misc.options({"table_name": "rayyan", "row_id": "id"}).flatten()
merged = flat.merge(clean, on=["id", "attribute"], how="inner")
neq = ~((merged["value"] == merged["correct_val"])
        | (merged["value"].isna() & merged["correct_val"].isna()))
delphi.register_table(
    "error_cells_ground_truth",
    merged[neq][["id", "attribute"]].reset_index(drop=True))

repaired_df = delphi.repair \
    .setTableName("rayyan") \
    .setRowId("id") \
    .setErrorCells("error_cells_ground_truth") \
    .setDiscreteThreshold(400) \
    .run()

pdf = repaired_df.merge(clean, on=["id", "attribute"], how="inner")
rdf = delphi.table("error_cells_ground_truth") \
    .merge(repaired_df, on=["id", "attribute"], how="left") \
    .merge(clean, on=["id", "attribute"], how="left")

nse = lambda a, b: (a == b) | (a.isna() & b.isna())
precision = float(nse(pdf["repaired"], pdf["correct_val"]).mean()) if len(pdf) else 0.0
recall = float(nse(rdf["repaired"], rdf["correct_val"]).mean())
f1 = 2 * precision * recall / (precision + recall + 1e-4)
print(f"Precision={precision} Recall={recall} F1={f1}")
