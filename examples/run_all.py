"""Runs every example end-to-end and captures its transcript next to the
script (`examples/<name>.py.out`), mirroring the reference's committed
`resources/examples/*.py.out` evidence files.

Usage: python examples/run_all.py [--cpu] [names...]

Each transcript records the example's stdout (repairs and P/R/F1 lines).
tax.py / movies.py need datasets the reference checkout does not bundle
(testdata/raha ships only beers/flights/rayyan); they are skipped with a
note unless a data dir is supplied via DELPHI_RAHA_EXTRA.
"""

import argparse
import contextlib
import io
import os
import runpy
import sys
import time

EXAMPLES_DIR = os.path.dirname(os.path.abspath(__file__))

# insertion order = cheap first
ALL = ["adult", "iris", "boston", "error_detectors", "flights", "beers",
       "rayyan", "hospital", "hospital_preprocess_blocking", "tax", "movies"]
NEEDS_EXTRA_DATA = {"tax", "movies"}


def run_one(name: str) -> str:
    path = os.path.join(EXAMPLES_DIR, f"{name}.py")
    out = io.StringIO()
    t0 = time.time()
    status = "ok"
    old_argv = sys.argv
    extra = os.environ.get("DELPHI_RAHA_EXTRA")
    sys.argv = [path] + ([extra] if name in NEEDS_EXTRA_DATA and extra else [])
    try:
        with contextlib.redirect_stdout(out):
            runpy.run_path(path, run_name="__main__")
    except SystemExit as e:
        if e.code not in (0, None):
            status = f"exit {e.code}"
    except Exception as e:  # noqa: BLE001 - transcript records the failure
        status = f"error: {e.__class__.__name__}: {e}"
    finally:
        sys.argv = old_argv
    elapsed = time.time() - t0
    transcript = out.getvalue()
    transcript += f"\n[{name}.py finished: {status}, {elapsed:.1f}s]\n"
    with open(path + ".out", "w") as f:
        f.write(transcript)
    print(f"{name}: {status} ({elapsed:.1f}s)", file=sys.stderr)
    return transcript


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("names", nargs="*", default=None)
    parser.add_argument("--cpu", action="store_true",
                        help="force the CPU backend (replicates tests/conftest)")
    args = parser.parse_args()

    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
        try:
            import jax._src.xla_bridge as xb
            xb._backend_factories.pop("axon", None)
        except Exception:
            pass

    names = args.names or ALL
    for name in names:
        if name in NEEDS_EXTRA_DATA and not os.environ.get("DELPHI_RAHA_EXTRA"):
            note = (f"{name}.py: dataset not bundled in this reference "
                    "checkout (testdata/raha ships only beers/flights/"
                    "rayyan); set DELPHI_RAHA_EXTRA=<dir> to run it\n")
            with open(os.path.join(EXAMPLES_DIR, f"{name}.py.out"), "w") as f:
                f.write(note)
            print(note.strip(), file=sys.stderr)
            continue
        run_one(name)


if __name__ == "__main__":
    main()
