"""Raha beers repair with ground-truth error cells
(reference resources/examples/beers.py): a known-hard dataset — the reference
transcript records P/R/F1 = 0.0551. Only the 'state' attribute is targeted;
the other erroneous attrs carry format errors a categorical repairer cannot
reproduce.

    python examples/beers.py [path-to-raha-testdata]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pandas as pd

from delphi_tpu import delphi

TESTDATA = sys.argv[1] if len(sys.argv) > 1 else "/root/reference/testdata/raha"

beers = pd.read_csv(f"{TESTDATA}/beers.csv", dtype=str)
clean = pd.read_csv(f"{TESTDATA}/beers_clean.csv", dtype=str)
delphi.register_table("beers", beers)

flat = delphi.misc.options({"table_name": "beers", "row_id": "index"}).flatten()
merged = flat.merge(clean, on=["index", "attribute"], how="inner")
neq = ~((merged["value"] == merged["correct_val"])
        | (merged["value"].isna() & merged["correct_val"].isna()))
delphi.register_table(
    "error_cells_ground_truth",
    merged[neq][["index", "attribute"]].reset_index(drop=True))

repaired_df = delphi.repair \
    .setTableName("beers") \
    .setRowId("index") \
    .setErrorCells("error_cells_ground_truth") \
    .setTargets(["state"]) \
    .setDiscreteThreshold(600) \
    .run()

pdf = repaired_df.merge(clean, on=["index", "attribute"], how="inner")
gt = delphi.table("error_cells_ground_truth")
rdf = gt[gt["attribute"] == "state"] \
    .merge(repaired_df, on=["index", "attribute"], how="left") \
    .merge(clean, on=["index", "attribute"], how="left")

nse = lambda a, b: (a == b) | (a.isna() & b.isna())
precision = float(nse(pdf["repaired"], pdf["correct_val"]).mean())
recall = float(nse(rdf["repaired"], rdf["correct_val"]).mean())
f1 = 2 * precision * recall / (precision + recall + 1e-4)
print(f"Precision={precision} Recall={recall} F1={f1}")
