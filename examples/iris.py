"""Continuous-attribute regression repair on iris
(reference resources/examples/iris.py): NULL cells are filled by the JAX GBDT
regressors and scored as RMSE/MAE against the clean data.

    python examples/iris.py [path-to-testdata]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import pandas as pd

from delphi_tpu import delphi, NullErrorDetector

TESTDATA = sys.argv[1] if len(sys.argv) > 1 else "/root/reference/bin/testdata"

delphi.register_table("iris", pd.read_csv(f"{TESTDATA}/iris.csv"))
clean = pd.read_csv(f"{TESTDATA}/iris_clean.csv")

repaired_df = delphi.repair \
    .setInput("iris") \
    .setRowId("tid") \
    .setErrorDetectors([NullErrorDetector()]) \
    .run()

cmp = repaired_df.merge(clean, on=["tid", "attribute"], how="inner")
err = cmp["correct_val"].astype(float) - cmp["repaired"].astype(float)
rmse = float(np.sqrt((err ** 2).mean()))
mae = float(err.abs().mean())
print(f"RMSE={rmse} MAE={mae}")
