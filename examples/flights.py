"""Raha flights repair with ground-truth error cells
(reference resources/examples/flights.py) — the headline benchmark workload,
also runnable via `python bench.py`.

    python examples/flights.py [path-to-raha-testdata]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pandas as pd

from delphi_tpu import delphi

TESTDATA = sys.argv[1] if len(sys.argv) > 1 else "/root/reference/testdata/raha"

flights = pd.read_csv(f"{TESTDATA}/flights.csv", dtype=str)
clean = pd.read_csv(f"{TESTDATA}/flights_clean.csv", dtype=str)
delphi.register_table("flights", flights)

# ground truth: flattened cells that differ from the clean values
flat = delphi.misc.options({"table_name": "flights", "row_id": "tuple_id"}).flatten()
merged = flat.merge(clean, on=["tuple_id", "attribute"], how="inner")
neq = ~((merged["value"] == merged["correct_val"])
        | (merged["value"].isna() & merged["correct_val"].isna()))
delphi.register_table(
    "error_cells_ground_truth",
    merged[neq][["tuple_id", "attribute"]].reset_index(drop=True))

repaired_df = delphi.repair \
    .setTableName("flights") \
    .setRowId("tuple_id") \
    .setErrorCells("error_cells_ground_truth") \
    .setDiscreteThreshold(400) \
    .run()

pdf = repaired_df.merge(clean, on=["tuple_id", "attribute"], how="inner")
rdf = delphi.table("error_cells_ground_truth") \
    .merge(repaired_df, on=["tuple_id", "attribute"], how="left") \
    .merge(clean, on=["tuple_id", "attribute"], how="left")

nse = lambda a, b: (a == b) | (a.isna() & b.isna())
precision = float(nse(pdf["repaired"], pdf["correct_val"]).mean())
recall = float(nse(rdf["repaired"], rdf["correct_val"]).mean())
f1 = 2 * precision * recall / (precision + recall)
print(f"Precision={precision} Recall={recall} F1={f1}")
