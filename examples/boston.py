"""Mixed discrete/continuous repair on boston
(reference resources/examples/boston.py): detect errors with the default
detectors, repair discrete attrs (scored as precision/recall) and continuous
attrs (scored as RMSE/MAE) against boston_clean.

    python examples/boston.py [path-to-testdata]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import pandas as pd

from delphi_tpu import delphi

TESTDATA = sys.argv[1] if len(sys.argv) > 1 else "/root/reference/testdata"

# The reference casts a subset of columns to numeric types via an explicit
# schema (resources/examples/boston.py: boston_schema); mirror that here.
CONTINUOUS = ["CRIM", "RM", "DIS", "B", "LSTAT"]
INTEGRAL = ["ZN", "TAX"]

boston = pd.read_csv(f"{TESTDATA}/boston.csv", dtype=str)
boston["tid"] = boston["tid"].astype(int)
for c in CONTINUOUS:
    boston[c] = boston[c].astype(float)
for c in INTEGRAL:
    boston[c] = boston[c].astype("Int64")
clean = pd.read_csv(f"{TESTDATA}/boston_clean.csv", dtype=str)
clean["tid"] = clean["tid"].astype(int)
delphi.register_table("boston", boston)

repaired_df = delphi.repair \
    .setTableName("boston") \
    .setRowId("tid") \
    .setDiscreteThreshold(30) \
    .run()

pdf = repaired_df.merge(clean, on=["tid", "attribute"], how="inner")

is_discrete = ~pdf["attribute"].isin(["CRIM", "LSTAT"])
discrete = pdf[is_discrete]
nse = lambda a, b: (a.astype(str) == b.astype(str)) | (a.isna() & b.isna())
hits = nse(discrete["repaired"], discrete["correct_val"])
precision = recall = float(hits.mean()) if len(discrete) else float("nan")
f1 = (2 * precision * recall) / (precision + recall) if precision + recall else 0.0
print(f"Precision={precision} Recall={recall} F1={f1}")

continuous = pdf[~is_discrete]
err = continuous["correct_val"].astype(float) - continuous["repaired"].astype(float)
rmse = float(np.sqrt((err ** 2).mean()))
mae = float(err.abs().mean())
print(f"RMSE={rmse} MAE={mae} RMSE/MAE={rmse / mae}")
